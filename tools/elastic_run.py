#!/usr/bin/env python
"""Elastic trainer supervisor: relaunch a training command on failure,
ELASTIC_EXIT_CODE (101), or fleet-membership shrink — no operator glue.

usage:
    # host 0 also hosts the rendezvous store (survives trainer restarts):
    python tools/elastic_run.py --host-store --master 10.0.0.1:7777 \
        --watch --np 4 --rank 0 -- python train.py --resume ./ckpt
    # every other host (rank 1..3):
    python tools/elastic_run.py --master 10.0.0.1:7777 \
        --watch --np 4 --rank 1 -- python train.py --resume ./ckpt

The supervisor exports the full trainer env contract to the child
(MASTER_ADDR/PORT, PADDLE_TRAINERS_NUM from --np, PADDLE_TRAINER_ID from
--rank, and a stable PADDLE_CURRENT_ENDPOINT member id), so the
coordinated checkpoint barrier works without any extra operator env.

The supervised command is relaunched with `PADDLE_TPU_ELASTIC_RESTART_NUM`
bumped each generation, which the coordinated-checkpoint barrier
(`CheckpointCoordinator`) uses to namespace its store keys — so the
relaunched `Model.fit(resume=...)` resumes from the newest step committed
on EVERY host. With `--watch`, a peer whose heartbeat goes stale (and that
has not published its done-flag) triggers a local SIGTERM + relaunch, so
the whole fleet re-enters the same generation together.

Changed world size (elastic re-sharding): when the trainer checkpoints
with the SHARDED layout (`FaultTolerantCheckpoint(layout="sharded")`,
one shared directory for the whole fleet), the operator may relaunch the
supervisors with a DIFFERENT `--np` — e.g. 2 preempted hosts resumed as
1, or 1 scaled up to 2. Each new rank re-shards the checkpoint onto its
mesh at restore (`distributed/sharded_checkpoint.py`), and fleet
membership is namespaced by fleet size, so stale member registrations
from the old world size in a long-lived `--host-store` rendezvous store
cannot wedge the new fleet's watch. The classic per-host file layout
still requires relaunching with the SAME --np.

Self-driving fleet (`--controller[=dry-run]`, pass on ANY number of
hosts — the controllers lease-elect ONE leader over the rendezvous
store; the rest stand by and take over within one lease TTL): each
supervisor given the flag runs a FleetController
(`distributed/fleet/controller.py`) on a background aggregator poll —
a confirmed persistent straggler is EVICTED (every supervisor relaunches
its trainer at N-1 with re-densified ranks, resuming from the newest
fleet-committed step via the sharded re-sharding restore, while the
evicted host's supervisor holds its trainer on probation) and READMITTED
once its probation heartbeat has been fresh for the cooldown; one host's
`diverged` health status escalates to a fleet-wide coordinated ROLLBACK
(hard kill + relaunch with PADDLE_TPU_RESUME_VALID_ONLY=1 so every host
restores the same last numerically-valid committed step). Every
supervisor of a >=2 fleet subscribes to the command ledger
automatically; `--controller=dry-run` logs `controller_decision` events
without acting. Controller actions never consume the restart budget.

Knobs (flags override env): --max-restarts / PADDLE_TPU_ELASTIC_MAX_RESTARTS
(default 3), --backoff / PADDLE_TPU_ELASTIC_BACKOFF (base seconds, doubled
per restart, capped by PADDLE_TPU_ELASTIC_BACKOFF_MAX), --ttl /
PADDLE_ELASTIC_TTL (heartbeat staleness),
PADDLE_TPU_ELASTIC_BUDGET_RESET_SEC (sustained-healthy budget reset),
PADDLE_TPU_CONTROLLER_{CONFIRM_WINDOWS,READMIT_SEC,POLL_SEC,MIN_WORLD}.
Restarts land in `elastic_restarts_total{reason=}`; decisions in
`controller_decisions_total{policy=,outcome=}`.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="elastic auto-restart supervisor",
        usage="elastic_run.py [options] -- prog [args...]")
    p.add_argument("--master", default=None,
                   help="host:port of the rendezvous TCPStore "
                        "(default: $MASTER_ADDR:$MASTER_PORT)")
    p.add_argument("--host-store", action="store_true",
                   help="host the rendezvous store in THIS supervisor "
                        "(it outlives trainer restarts); port 0 picks one")
    p.add_argument("--watch", action="store_true",
                   help="watch fleet membership and restart the local "
                        "trainer when a peer's heartbeat goes stale")
    p.add_argument("--controller", nargs="?", const="on", default=None,
                   choices=["on", "dry-run"],
                   help="run the self-driving fleet controller in THIS "
                        "supervisor. Pass it on any number of hosts: "
                        "controllers lease-elect one leader over the "
                        "rendezvous store (term-fenced; standbys take "
                        "over within PADDLE_TPU_CONTROLLER_LEASE_TTL "
                        "seconds and inherit the decision ledger). The "
                        "leader consumes fleet digests + "
                        "health/straggler signals and acts — evicts a "
                        "confirmed straggler (fleet relaunches at N-1, "
                        "scales back on readmission), escalates one "
                        "host's divergence to a fleet-wide rollback. "
                        "--controller=dry-run logs every decision "
                        "without acting")
    p.add_argument("--np", type=int, default=None,
                   help="fleet size (exported to the trainer as "
                        "PADDLE_TRAINERS_NUM; also the --watch quorum; "
                        "default $PADDLE_TRAINERS_NUM or 1)")
    p.add_argument("--rank", type=int, default=None,
                   help="this host's rank (exported as PADDLE_TRAINER_ID; "
                        "default $PADDLE_TRAINER_ID — resolved in main() "
                        "so a garbled env value exits 2, not a traceback)")
    p.add_argument("--ttl", type=float, default=None,
                   help="heartbeat TTL seconds (default $PADDLE_ELASTIC_TTL "
                        "or 10)")
    p.add_argument("--max-restarts", type=int, default=None)
    p.add_argument("--backoff", type=float, default=None)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="training command (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no training command given (append: -- prog args...)")
    args.cmd = cmd
    return args


def main(argv=None) -> int:
    args = parse_args(argv)
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticSupervisor)

    # env fallbacks resolve HERE (not at argparse definition time) so a
    # garbled value exits 2 with a message, like every other config error
    if args.rank is None and os.environ.get("PADDLE_TRAINER_ID"):
        raw = os.environ["PADDLE_TRAINER_ID"]
        try:
            args.rank = int(raw)
        except ValueError:
            print(f"[elastic_run] invalid $PADDLE_TRAINER_ID {raw!r} "
                  f"(expected an integer rank)", file=sys.stderr)
            return 2
    if args.np is None:
        raw = os.environ.get("PADDLE_TRAINERS_NUM", "1")
        try:
            args.np = int(raw)
        except ValueError:
            print(f"[elastic_run] invalid $PADDLE_TRAINERS_NUM {raw!r} "
                  f"(expected an integer fleet size)", file=sys.stderr)
            return 2

    master = args.master or "{}:{}".format(
        os.environ.get("MASTER_ADDR", "127.0.0.1"),
        os.environ.get("MASTER_PORT", "0"))
    host, _, port = master.rpartition(":")
    if not host or not port.isdigit():
        # an empty/garbled port would propagate as MASTER_PORT="" and the
        # trainer would silently skip the checkpoint barrier (single-host
        # fallback) while its peers wait on it — fail loudly here instead
        print(f"[elastic_run] invalid --master {master!r} "
              f"(expected host:port)", file=sys.stderr)
        return 2

    env = {}
    server = None
    if args.host_store:
        from paddle_tpu.distributed.store import TCPStore
        server = TCPStore(host, int(port), is_master=True)
        port = str(server.port)
        print(f"[elastic_run] hosting rendezvous store on {host}:{port}",
              file=sys.stderr)
    # export the FULL trainer env contract — coordinator_from_env needs
    # PADDLE_TRAINERS_NUM/PADDLE_TRAINER_ID too, and without them the
    # coordinated barrier silently degrades to per-host local saves
    env["MASTER_ADDR"], env["MASTER_PORT"] = host, str(port)
    env["PADDLE_TRAINERS_NUM"] = str(args.np)
    if args.rank is not None:
        env["PADDLE_TRAINER_ID"] = str(args.rank)
    elif args.np > 1 and os.environ.get("PADDLE_TPU_CKPT_BARRIER", "1") != "0":
        # coordinator_from_env REQUIRES a distinct rank in a >=2 fleet, so
        # the child would crash at startup on every relaunch until the
        # restart budget burned out on an unfixable config error — fail
        # here like the garbled --master path does
        print("[elastic_run] fleet size > 1 needs --rank / "
              "$PADDLE_TRAINER_ID (a distinct rank per host) for the "
              "coordinated checkpoint barrier; pass --rank or set "
              "PADDLE_TPU_CKPT_BARRIER=0 to opt out", file=sys.stderr)
        if server is not None:
            server.stop()
        return 2
    # a STABLE member id for the trainer (ElasticManager's default):
    # host-<pid> would change every relaunch, leaving the dead
    # generation's id registered forever (never alive, never done) and
    # wedging every peer's membership watch
    endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT")
    if not endpoint and args.rank is not None:
        endpoint = f"trainer-{args.rank}"
    if endpoint:
        env["PADDLE_CURRENT_ENDPOINT"] = endpoint
    elif args.watch:
        # --watch with no stable member id would register the trainer as
        # host-<pid>, which changes every relaunch: after the first crash
        # the dead pid-id stays in the member set forever (never alive,
        # never done) and EVERY watching supervisor SIGTERMs each fresh
        # relaunch (reason 'membership') until its restart budget dies
        print("[elastic_run] --watch needs a stable trainer member id: "
              "pass --rank (member id becomes trainer-<rank>) or set "
              "$PADDLE_CURRENT_ENDPOINT", file=sys.stderr)
        if server is not None:
            server.stop()
        return 2

    # observability: a child running Model.fit serves
    # PADDLE_TPU_METRICS_PORT itself; the supervisor serves the SUPERVISOR
    # port (default +1) — it outlives trainer relaunches, so its /healthz
    # shows the restart gap as a growing fleet step age, and its /metrics
    # carries host-labeled fleet_* families aggregated from every rank's
    # digest. The aggregator is built EXPLICITLY from --master (never by
    # mutating this process's env — main() may run in-process and env
    # leaks would rewrite the trainer contract of everything after it).
    # The fleet CONTROLLER needs the aggregator too, with or without an
    # observability server.
    agg = None
    if args.np > 1 and (args.controller
                        or os.environ.get("PADDLE_TPU_METRICS_PORT", "")):
        try:
            from paddle_tpu.distributed.fleet.telemetry import (
                FleetAggregator)
            from paddle_tpu.distributed.store import TCPStore
            agg = FleetAggregator(
                TCPStore(host, int(port), timeout=10), args.np)
        except Exception as e:
            print(f"[elastic_run] fleet aggregation unavailable: "
                  f"{e}", file=sys.stderr)
    if os.environ.get("PADDLE_TPU_METRICS_PORT", ""):
        try:
            from paddle_tpu.profiler import server as _obs_server
            _obs_server.maybe_start_server(role="supervisor",
                                           aggregator=agg)
        except Exception as e:
            print(f"[elastic_run] observability server unavailable: {e}",
                  file=sys.stderr)

    manager = None
    member_mgr = None
    if args.watch:
        if args.ttl is not None:
            os.environ["PADDLE_ELASTIC_TTL"] = str(args.ttl)
        # watch-only manager under its own id: the supervisor must not
        # mask the fleet's state with beats attributed to a trainer
        manager = ElasticManager(host_id=f"supervisor-{os.getpid()}",
                                 master=f"{host}:{port}", np=args.np)
        # register + heartbeat the CHILD's member id from the supervisor:
        # ordinary trainers never construct an ElasticManager themselves,
        # and with no registered members every peer's watch is inert
        # (fleet never "assembles"). Supervisor liveness == host liveness:
        # a hard host death kills this process too, its beat goes stale,
        # and peers detect it — while a mere child relaunch gap keeps
        # beating and must NOT look like a dead host (self-exclusion
        # covers our own watch; this covers the peers'). A trainer that
        # does join under the same id is harmless: member ids dedupe.
        member_mgr = ElasticManager(host_id=endpoint,
                                    master=f"{host}:{port}", np=args.np)
        member_mgr.join()

    # self-driving fleet: every supervisor of a >=2 fleet subscribes to
    # the controller command ledger (evict / readmit / rollback); the
    # host given --controller ALSO runs the decision loop on a background
    # aggregator poll (so detection never depends on an external scraper)
    bus = None
    controller = None
    if args.np > 1 and endpoint:
        try:
            from paddle_tpu.distributed.fleet.controller import (
                ControllerCommandBus)
            from paddle_tpu.distributed.store import TCPStore
            # own connection: the native store client is one socket and
            # the supervisor polls commands from its child-wait loop
            bus = ControllerCommandBus(TCPStore(host, int(port), timeout=10))
        except Exception as e:
            print(f"[elastic_run] controller command bus unavailable: {e}",
                  file=sys.stderr)
    if args.controller:
        if agg is None or bus is None:
            print("[elastic_run] --controller needs a >=2 fleet with "
                  "--rank/$PADDLE_CURRENT_ENDPOINT and a reachable "
                  "rendezvous store", file=sys.stderr)
            if server is not None:
                server.stop()
            return 2
        from paddle_tpu.distributed.fleet.controller import (
            controller_from_env)
        from paddle_tpu.distributed.store import TCPStore
        # the controller publishes from the aggregator's poll thread —
        # give it a bus on its OWN connection, distinct from the one the
        # supervisor polls in the child-wait loop
        controller = controller_from_env(
            agg, TCPStore(host, int(port), timeout=10),
            world_size=args.np, dry_run=(args.controller == "dry-run"))
        agg.start_polling(hook=controller.on_collect)
        role = "leader-elect" if controller.lease is not None else "solo"
        print(f"[elastic_run] fleet controller active "
              f"({'dry-run' if controller.dry_run else 'acting'}, {role}, "
              f"confirm_windows={controller.confirm_windows})",
              file=sys.stderr)

    # the id the LOCAL trainer registers under: exclude it from the
    # membership watch — the supervisor monitors its own child by process
    # exit, and the child's restart gap must not read as a stale fleet
    # member (that would re-SIGTERM the fresh relaunch)
    sup = ElasticSupervisor(max_restarts=args.max_restarts,
                            backoff=args.backoff, manager=manager,
                            self_member=endpoint, commands=bus)

    def on_fleet_change(cmd, held):
        """A controller command changed the fleet contract: re-join
        membership under the NEW fleet-size namespace (membership keys
        are namespaced by np, so the old world's registrations cannot
        wedge the new one's watch). A held (evicted) host leaves
        membership entirely until readmission."""
        nonlocal manager, member_mgr
        if not args.watch:
            return
        new_np = int(cmd.get("np") or args.np)
        if member_mgr is not None:
            try:
                member_mgr.exit()
            except Exception:
                pass
            member_mgr = None
        manager = None
        sup.manager = None
        if held:
            return
        manager = ElasticManager(host_id=f"supervisor-{os.getpid()}",
                                 master=f"{host}:{port}", np=new_np)
        member_mgr = ElasticManager(host_id=endpoint,
                                    master=f"{host}:{port}", np=new_np)
        member_mgr.join()
        sup.manager = manager

    sup.on_fleet_change = on_fleet_change
    rc = 1
    try:
        rc = sup.supervise(args.cmd, env=env)
        return rc
    finally:
        if controller is not None:
            # held peers poll ctl/job_done to exit cleanly once the fleet
            # is finished (with or without them) — but only the LEADER
            # declares the job done; a standby exiting must not tear the
            # fleet down under the live leader
            try:
                if controller.is_leader():
                    controller.bus.mark_job_done()
            except Exception:
                pass
            try:
                agg.stop_polling()
            except Exception:
                pass
            if controller.lease is not None:
                # voluntary handoff: deleting the lease lets a standby
                # take over immediately instead of waiting out the TTL
                try:
                    controller.lease.release()
                except Exception:
                    pass
            from paddle_tpu.distributed.fleet.controller import (
                set_controller)
            set_controller(None)
        if member_mgr is not None:
            if rc == 0:
                member_mgr.exit()  # clean deregistration (done-flag is set)
            else:
                # budget exhausted: keep the member REGISTERED and let the
                # beat go stale so peers detect the dead host — exit()'s
                # tombstone would shrink the member list below np and make
                # this death invisible to every peer's watch
                member_mgr.abandon()
        if server is not None:
            server.stop()


if __name__ == "__main__":
    sys.exit(main())
